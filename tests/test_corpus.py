"""Byte-level corpus loader: round-trip, shapes, BERT-recipe masking, and
end-to-end training on real text."""

import numpy as np
import pytest

from mpi_tensorflow_tpu.data import corpus

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def text_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("corpus") / "tiny.txt"
    p.write_text("the quick brown fox jumps over the lazy dog. " * 200)
    return str(p)


class TestTokenizer:
    def test_roundtrip(self):
        s = "hello, TPU framework! éè"
        ids = corpus.encode_bytes(s)
        assert ids.dtype == np.int32 and ids.min() >= 5
        assert corpus.decode_bytes(ids).decode("utf-8") == s

    def test_sequences_shape_and_truncation(self, text_file):
        toks = corpus.sequences_from_file(text_file, seq_len=64)
        assert toks.ndim == 2 and toks.shape[1] == 64
        assert toks.dtype == np.int32
        toks4 = corpus.sequences_from_file(text_file, seq_len=64,
                                           max_sequences=4)
        assert toks4.shape[0] == 4

    def test_too_short_raises(self, tmp_path):
        p = tmp_path / "short.txt"
        p.write_text("abc")
        with pytest.raises(ValueError, match="shorter"):
            corpus.sequences_from_file(str(p), seq_len=64)


class TestMasking:
    def test_bert_recipe(self, text_file):
        inputs, targets, mask = corpus.load_mlm(text_file, seq_len=64,
                                                mask_rate=0.3, seed=0)
        assert inputs.shape == targets.shape == mask.shape
        assert 0.2 < mask.mean() < 0.4
        sel = mask & (inputs == corpus.MASK_TOKEN)
        # ~80% of masked positions carry the mask token
        assert 0.6 < sel.sum() / mask.sum() < 0.95
        # unmasked positions are untouched
        np.testing.assert_array_equal(inputs[~mask], targets[~mask])

    def test_deterministic(self, text_file):
        a = corpus.load_mlm(text_file, seq_len=64, seed=7)
        b = corpus.load_mlm(text_file, seq_len=64, seed=7)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


class TestLoopIntegration:
    @pytest.mark.parametrize("model_name", ["bert_base", "gpt_base"])
    def test_train_mlm_on_text_file(self, text_file, model_name):
        import dataclasses

        from mpi_tensorflow_tpu.config import Config
        from mpi_tensorflow_tpu.models import bert
        from mpi_tensorflow_tpu.parallel import mesh as meshlib
        from mpi_tensorflow_tpu.train import mlm_loop

        cfg = Config(epochs=2, batch_size=4, log_every=16, seed=1,
                     model=model_name, text_file=text_file)
        tiny = dataclasses.replace(bert.BERT_TINY,
                                   vocab_size=corpus.BYTE_VOCAB)
        res = mlm_loop.train_mlm(cfg, bert_cfg=tiny,
                                 mesh=meshlib.make_mesh({"data": 8}),
                                 seq_len=32, learning_rate=3e-3,
                                 verbose=False)
        assert np.isfinite(res.final_error)
        assert res.num_steps > 0


class TestEndToEnd:
    def test_mlm_trains_on_real_text(self, text_file):
        import dataclasses

        import jax
        import jax.numpy as jnp
        import optax

        from mpi_tensorflow_tpu.models import bert
        from mpi_tensorflow_tpu.parallel import mesh as meshlib
        from mpi_tensorflow_tpu.train import gspmd

        cfg = dataclasses.replace(bert.BERT_TINY,
                                  vocab_size=corpus.BYTE_VOCAB)
        mesh = meshlib.make_mesh({"data": 8})
        model = bert.BertMlm(cfg, mesh=mesh)
        tx = optax.adamw(3e-3)
        state = gspmd.init_gspmd_state(model, tx, jax.random.key(0), mesh)
        step = gspmd.make_gspmd_train_step(model, mesh, tx)
        inputs, targets, mask = corpus.load_mlm(text_file, seq_len=32,
                                                max_sequences=16)
        batch = gspmd.shard_batch(
            {"tokens": jnp.asarray(inputs), "mask": jnp.asarray(mask)}, mesh)
        tgt = gspmd.shard_batch(jnp.asarray(targets), mesh)
        losses = []
        for i in range(8):
            state, m = step(state, batch, tgt, jax.random.key(i))
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        # highly repetitive text: the model should make quick progress
        assert losses[-1] < losses[0] - 0.5, losses