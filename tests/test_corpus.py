"""Byte-level corpus loader: round-trip, shapes, BERT-recipe masking, and
end-to-end training on real text."""

import numpy as np
import pytest

from mpi_tensorflow_tpu.data import corpus

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def text_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("corpus") / "tiny.txt"
    p.write_text("the quick brown fox jumps over the lazy dog. " * 200)
    return str(p)


class TestTokenizer:
    def test_roundtrip(self):
        s = "hello, TPU framework! éè"
        ids = corpus.encode_bytes(s)
        assert ids.dtype == np.int32 and ids.min() >= 5
        assert corpus.decode_bytes(ids).decode("utf-8") == s

    def test_sequences_shape_and_truncation(self, text_file):
        toks = corpus.sequences_from_file(text_file, seq_len=64)
        assert toks.ndim == 2 and toks.shape[1] == 64
        assert toks.dtype == np.int32
        toks4 = corpus.sequences_from_file(text_file, seq_len=64,
                                           max_sequences=4)
        assert toks4.shape[0] == 4

    def test_too_short_raises(self, tmp_path):
        p = tmp_path / "short.txt"
        p.write_text("abc")
        with pytest.raises(ValueError, match="shorter"):
            corpus.sequences_from_file(str(p), seq_len=64)


class TestMasking:
    def test_bert_recipe(self, text_file):
        inputs, targets, mask = corpus.load_mlm(text_file, seq_len=64,
                                                mask_rate=0.3, seed=0)
        assert inputs.shape == targets.shape == mask.shape
        assert 0.2 < mask.mean() < 0.4
        sel = mask & (inputs == corpus.MASK_TOKEN)
        # ~80% of masked positions carry the mask token
        assert 0.6 < sel.sum() / mask.sum() < 0.95
        # unmasked positions are untouched
        np.testing.assert_array_equal(inputs[~mask], targets[~mask])

    def test_deterministic(self, text_file):
        a = corpus.load_mlm(text_file, seq_len=64, seed=7)
        b = corpus.load_mlm(text_file, seq_len=64, seed=7)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


class TestLoopIntegration:
    @pytest.mark.parametrize("model_name", ["bert_base", "gpt_base"])
    def test_train_mlm_on_text_file(self, text_file, model_name):
        import dataclasses

        from mpi_tensorflow_tpu.config import Config
        from mpi_tensorflow_tpu.models import bert
        from mpi_tensorflow_tpu.parallel import mesh as meshlib
        from mpi_tensorflow_tpu.train import mlm_loop

        cfg = Config(epochs=2, batch_size=4, log_every=16, seed=1,
                     model=model_name, text_file=text_file)
        tiny = dataclasses.replace(bert.BERT_TINY,
                                   vocab_size=corpus.BYTE_VOCAB)
        res = mlm_loop.train_mlm(cfg, bert_cfg=tiny,
                                 mesh=meshlib.make_mesh({"data": 8}),
                                 seq_len=32, learning_rate=3e-3,
                                 verbose=False)
        assert np.isfinite(res.final_error)
        assert res.num_steps > 0


class TestEndToEnd:
    def test_mlm_trains_on_real_text(self, text_file):
        import dataclasses

        import jax
        import jax.numpy as jnp
        import optax

        from mpi_tensorflow_tpu.models import bert
        from mpi_tensorflow_tpu.parallel import mesh as meshlib
        from mpi_tensorflow_tpu.train import gspmd

        cfg = dataclasses.replace(bert.BERT_TINY,
                                  vocab_size=corpus.BYTE_VOCAB)
        mesh = meshlib.make_mesh({"data": 8})
        model = bert.BertMlm(cfg, mesh=mesh)
        tx = optax.adamw(3e-3)
        state = gspmd.init_gspmd_state(model, tx, jax.random.key(0), mesh)
        step = gspmd.make_gspmd_train_step(model, mesh, tx)
        inputs, targets, mask = corpus.load_mlm(text_file, seq_len=32,
                                                max_sequences=16)
        batch = gspmd.shard_batch(
            {"tokens": jnp.asarray(inputs), "mask": jnp.asarray(mask)}, mesh)
        tgt = gspmd.shard_batch(jnp.asarray(targets), mesh)
        losses = []
        for i in range(8):
            state, m = step(state, batch, tgt, jax.random.key(i))
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        # highly repetitive text: the model should make quick progress
        assert losses[-1] < losses[0] - 0.5, losses

class TestWordPiece:
    """Real-vocab tokenization (VERDICT r2 #8): greedy longest-match
    WordPiece from a user-supplied vocab.txt-layout file."""

    def _vocab(self):
        return corpus.WordPieceVocab(
            ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "the", "quick", "un", "##aff", "##able", "##ably", "aff",
             "run", "##ning", ",", "."])

    def test_longest_match_and_continuations(self):
        v = self._vocab()
        ids = v.encode("unaffable running")
        toks = [v.tokens[i] for i in ids]
        assert toks == ["un", "##aff", "##able", "run", "##ning"]

    def test_unmatchable_word_is_unk(self):
        v = self._vocab()
        ids = v.encode("the zzz quick")
        toks = [v.tokens[i] for i in ids]
        assert toks == ["the", "[UNK]", "quick"]

    def test_punctuation_split_and_lowercase(self):
        v = self._vocab()
        toks = [v.tokens[i] for i in v.encode("The quick, running.")]
        assert toks == ["the", "quick", ",", "run", "##ning", "."]

    def test_duplicate_vocab_raises(self):
        with pytest.raises(ValueError, match="duplicate"):
            corpus.WordPieceVocab(["a", "a"])

    def test_from_file_roundtrip(self, tmp_path):
        p = tmp_path / "vocab.txt"
        p.write_text("\n".join(["[PAD]", "[UNK]", "[MASK]", "hello",
                                "world"]) + "\n")
        v = corpus.WordPieceVocab.from_file(str(p))
        assert v.size == 5 and v.mask == 2
        assert [v.tokens[i] for i in v.encode("hello world")] \
            == ["hello", "world"]


@pytest.mark.quick
class TestNativeWordPiece:
    """The C++ batch encoder (native/wordpiece.cpp) must be bit-identical
    to the Python reference implementation on its ASCII contract — the
    same invariant the native IDX loader pins (data/native.py header)."""

    def _pair(self, tokens):
        """(vocab routed to native, vocab forced onto the Python path)."""
        from mpi_tensorflow_tpu.data import native

        if not native.WordPieceNative.available():
            pytest.skip("native toolchain unavailable")
        nat = corpus.WordPieceVocab(tokens)
        py = corpus.WordPieceVocab(tokens)
        py._native_tried = True     # force the reference implementation
        return nat, py

    def test_parity_on_random_ascii(self):
        import random

        pieces = ["[PAD]", "[UNK]", "[MASK]", "the", "quick", "brown",
                  "fox", "jump", "##s", "##ing", "##ed", "over", "lazy",
                  "dog", "run", "##ner", "a", "b", "##c", "'", ",", ".",
                  "!", "x", "##yz", "un", "##aff", "##able"]
        nat, py = self._pair(pieces)
        rng = random.Random(0)
        words = ["The", "quick", "BROWN", "fox", "jumps", "jumping",
                 "unaffable", "zzzz", "runner", "a'bc", "x", "!!", "a,b."]
        for trial in range(50):
            text = " ".join(rng.choices(words, k=rng.randrange(0, 40)))
            got = nat.encode(text)
            want = py.encode(text)
            assert got.dtype == want.dtype == __import__("numpy").int32
            assert got.tolist() == want.tolist(), text

    def test_native_engaged_for_ascii(self):
        nat, _ = self._pair(["[UNK]", "hi"])
        nat.encode("hi hi")
        assert nat._native is not None

    def test_control_char_whitespace_parity(self):
        # \x1c-\x1f are whitespace to Python str.isspace() but not to C
        # isspace — the native encoder must match Python exactly
        nat, py = self._pair(["[UNK]", "a", "b"])
        for ch in ("\x1c", "\x1d", "\x1e", "\x1f", "\x0b", "\x0c"):
            text = f"a{ch}b"
            assert nat.encode(text).tolist() == py.encode(text).tolist(), \
                repr(ch)

    def test_non_ascii_routes_to_python(self):
        nat, py = self._pair(["[UNK]", "caf", "##e", "hi"])
        # é lowers/classifies differently under Unicode — must NOT hit the
        # C++ path; both vocab objects agree because both use Python here
        assert nat.encode("café hi").tolist() == py.encode("café hi").tolist()

    def test_unk_less_vocab_raises_both_paths(self):
        nat, py = self._pair(["hello"])
        with pytest.raises(ValueError, match="no .UNK."):
            py.encode("zzz")
        with pytest.raises(ValueError, match="no .UNK."):
            nat.encode("zzz")

    def test_long_corpus_parity_at_max_density(self):
        # single-char vocab makes ids-per-byte ~1 — the tightest case for
        # the len(text) output-capacity bound in WordPieceNative.encode
        nat, py = self._pair(["[UNK]", "a", "##a", "b", "##b"])
        text = "".join(__import__("random").Random(1).choices("ab ", k=5000))
        assert nat.encode(text).tolist() == py.encode(text).tolist()


class TestFlagshipVocab:
    """The perf-critical path gets a real-data consumer: a 30522-entry
    vocabulary through masked packing + tied_softmax_ce (the flagship
    head), not the 261-entry byte scheme."""

    @pytest.fixture(scope="class")
    def vocab30k(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("vocab")
        words = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
        words += [f"w{i:05d}" for i in range(30522 - len(words))]
        p = d / "vocab.txt"
        p.write_text("\n".join(words) + "\n")
        return str(p)

    @pytest.fixture(scope="class")
    def text30k(self, tmp_path_factory, vocab30k):
        rng = np.random.default_rng(0)
        words = [f"w{i:05d}" for i in rng.integers(0, 30000, 4000)]
        d = tmp_path_factory.mktemp("text")
        p = d / "corpus.txt"
        p.write_text(" ".join(words))
        return str(p)

    def test_load_mlm_at_real_vocab(self, vocab30k, text30k):
        inp, tgt, mask = corpus.load_mlm(text30k, seq_len=64,
                                         vocab_file=vocab30k, seed=0)
        v = corpus.WordPieceVocab.from_file(vocab30k)
        assert inp.max() < v.size and inp.min() >= 0
        assert (inp[mask] == v.mask).mean() > 0.6   # ~80% of masked
        # targets hold the original ids everywhere
        assert (tgt[~mask] == inp[~mask]).all()

    def test_vocab30k_through_tied_softmax_ce(self, vocab30k, text30k):
        """The chunked tied-decoder CE at vocab 30522 on real-text tokens:
        finite loss, and chunked == dense logits CE."""
        import jax
        import jax.numpy as jnp

        from mpi_tensorflow_tpu.ops import mlm_head

        inp, tgt, mask = corpus.load_mlm(text30k, seq_len=64,
                                         vocab_file=vocab30k, seed=0)
        V, E = 30522, 32
        rng = np.random.default_rng(1)
        emb = jnp.asarray(rng.normal(size=(V, E)).astype(np.float32) * .05)
        bias = jnp.zeros((V,), jnp.float32)
        t = jnp.asarray(rng.normal(size=(2, 64, E)).astype(np.float32))
        labels = jnp.asarray(tgt[:2], jnp.int32)
        ce = mlm_head.tied_softmax_ce(t, emb, bias, labels, chunk=2048)
        assert np.isfinite(np.asarray(ce)).all()
        logits = jnp.einsum("bse,ve->bsv", t, emb) + bias
        want = jax.nn.logsumexp(logits, -1) - jnp.take_along_axis(
            logits, labels[..., None], -1)[..., 0]
        np.testing.assert_allclose(np.asarray(ce), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_loop_trains_at_real_vocab(self, vocab30k, text30k):
        """mlm_loop end-to-end with --vocab-file: the model's vocab axis
        adopts 30522 and the masked-packed head trains."""
        import dataclasses

        from mpi_tensorflow_tpu.config import Config
        from mpi_tensorflow_tpu.models import bert
        from mpi_tensorflow_tpu.parallel import mesh as meshlib
        from mpi_tensorflow_tpu.train import mlm_loop

        cfg = Config(epochs=2, batch_size=2, log_every=8, seed=1,
                     text_file=text30k, vocab_file=vocab30k)
        tiny = dataclasses.replace(bert.BERT_TINY, max_positions=64)
        res = mlm_loop.train_mlm(cfg, bert_cfg=tiny, mesh=meshlib.make_mesh(
            {"data": 8}), seq_len=64, learning_rate=1e-3, verbose=False)
        assert res.state.params["tok_emb"].shape[0] == 30522
        assert np.isfinite(res.final_error)

    def test_crlf_vocab_file(self, tmp_path):
        p = tmp_path / "vocab_crlf.txt"
        p.write_bytes(b"[PAD]\r\n[UNK]\r\n[MASK]\r\nhello\r\nworld\r\n")
        v = corpus.WordPieceVocab.from_file(str(p))
        assert [v.tokens[i] for i in v.encode("hello world")] \
            == ["hello", "world"]

    def test_random_replacements_exclude_specials(self):
        v = corpus.WordPieceVocab(
            ["[PAD]", "[UNK]", "[MASK]", "[unused0]", "aa", "bb"])
        assert v.random_replacement_ids().tolist() == [4, 5]
        toks = np.full((64, 64), 4, np.int32)
        inp, _, mask = corpus.mlm_from_tokens(
            toks, mask_rate=0.5, mask_token=v.mask,
            random_ids=v.random_replacement_ids(), seed=0)
        changed = inp[mask]
        assert set(np.unique(changed)) <= {2, 4, 5}   # [MASK] or non-special

    def test_missing_unk_fails_fast(self):
        v = corpus.WordPieceVocab(["[PAD]", "[MASK]", "hello"])
        with pytest.raises(ValueError, match="no .UNK."):
            v.encode("hello stranger")

    def test_streamed_max_sequences_matches_full_encode(self, tmp_path):
        v = corpus.WordPieceVocab(["[PAD]", "[UNK]", "[MASK]", "aa", "bb"])
        p = tmp_path / "big.txt"
        p.write_text("\n".join("aa bb aa" for _ in range(200)))
        full = corpus.sequences_from_file(str(p), seq_len=8, vocab=v)
        part = corpus.sequences_from_file(str(p), seq_len=8,
                                          max_sequences=3, vocab=v)
        np.testing.assert_array_equal(part, full[:3])
