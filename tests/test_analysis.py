"""graft-lint: the AST invariant checker (analysis/).

Every pass is proven LIVE with a red/green fixture pair: a minimal
synthetic source tree that violates the contract (the pass must flag
it) next to the corrected tree (the pass must stay silent).  The
fixtures are dicts of repo-relative path -> source text — exactly the
``run(sources)`` interface the real runner feeds from disk — so the
tests exercise the same discovery-by-content code paths as a live
scan.

Also pinned here:

- the PR 7 sticky-map race as a LOCK-HELD regression fixture (the
  read / health-check / LRU-touch split across two lock holds that
  shipped a KeyError);
- the bert ``causal`` shadowing case as a JIT-BRANCH precision
  regression (a nested def's param name must not taint an OUTER
  branch on a closure-captured static);
- allowlist comments (``sync-ok`` / ``lock-ok`` / ``jit-ok`` /
  ``noqa``) silencing each pass;
- the baseline ratchet: counts may only decrease, and the runner
  fails on any increase;
- the live repo itself scanning clean against the shipped baseline.

Host-only and fast (pure ``ast`` work, no jax arrays) — tier-1 safe.
"""

import json
import textwrap

from mpi_tensorflow_tpu.analysis import (core, host_sync, jit_stability,
                                         knob_bridge, locks, names,
                                         runner)


def _src(text):
    return textwrap.dedent(text).lstrip("\n")


def _ids(findings):
    return [f.pass_id for f in findings]


# ---------------------------------------------------------------------
# knob-bridge
# ---------------------------------------------------------------------

def _knob_tree(*, field="serve_knob: int = 1", flag_ok=True,
               wire_ok=True, guard_ok=True, post_init_ok=True,
               consume=True):
    """A minimal three-layer knob bridge, breakable one layer at a
    time."""
    # continuation lines carry the RAW indent the insertion point
    # needs, so textwrap.dedent sees a consistent block
    flag = ('p.add_argument("--serve-knob", type=int, default=1)'
            if flag_ok else
            'p.add_argument("--serve-knob", default=1)')
    wire = "serve_knob=args.serve_knob," if wire_ok else ""
    guard = ("if config.serve_knob < 1:\n"
             "                    raise SystemExit('bad')"
             if guard_ok else "pass")
    post = ("if self.knob < 1:\n"
            "                        raise ValueError('bad')"
            if post_init_ok else "pass")
    consumer = ("def use(serve):\n                return serve.knob\n"
                if consume else "")
    return {
        "pkg/cli.py": _src(f"""
            import argparse
            from pkg.config import Config

            def build_parser():
                p = argparse.ArgumentParser()
                {flag}
                return p

            def config_from_args(args):
                return Config({wire})

            def main(argv=None):
                args = build_parser().parse_args(argv)
                config = config_from_args(args)
                {guard}
                return config
            """),
        "pkg/config.py": _src(f"""
            import dataclasses

            @dataclasses.dataclass
            class Config:
                {field}
            """),
        "pkg/serve.py": _src(f"""
            import dataclasses

            @dataclasses.dataclass
            class ServeConfig:
                knob: int = 1

                def __post_init__(self):
                    {post}

                @classmethod
                def from_config(cls, cfg):
                    return cls(knob=cfg.serve_knob)
            {consumer}
            """),
    }


def test_knob_bridge_green():
    tree = _knob_tree()
    # guard against a vacuous pass: every fixture module must parse
    # and the content-based cli discovery must bite
    parsed = core.parse_sources(tree)
    assert len(parsed) == len(tree) == 3
    assert knob_bridge._find_cli(parsed) is not None
    assert knob_bridge.run(tree) == []


def test_knob_bridge_flag_without_field():
    tree = _knob_tree(field="other: int = 0")
    ids = _ids(knob_bridge.run(tree))
    assert "KNOB-FLAG" in ids


def test_knob_bridge_flag_not_wired():
    found = knob_bridge.run(_knob_tree(wire_ok=False))
    assert any(f.pass_id == "KNOB-FLAG" and "never wired" in f.message
               for f in found)


def test_knob_bridge_missing_main_guard():
    found = knob_bridge.run(_knob_tree(guard_ok=False))
    assert any(f.pass_id == "KNOB-GUARD" and "cli.main" in f.message
               for f in found)


def test_knob_bridge_missing_argparse_validation():
    found = knob_bridge.run(_knob_tree(flag_ok=False))
    assert any(f.pass_id == "KNOB-GUARD" and "argparse" in f.message
               for f in found)


def test_knob_bridge_missing_post_init_validation():
    found = knob_bridge.run(_knob_tree(post_init_ok=False))
    assert any(f.pass_id == "KNOB-GUARD"
               and "__post_init__ never validates" in f.message
               for f in found)


def test_knob_bridge_dead_field():
    tree = _knob_tree()
    tree["pkg/config.py"] = _src("""
        import dataclasses

        @dataclasses.dataclass
        class Config:
            serve_knob: int = 1
            serve_orphan: int = 0
        """)
    found = knob_bridge.run(tree)
    assert any(f.pass_id == "KNOB-DEAD" and "serve_orphan" in f.message
               for f in found)
    # the orphan also has no flag and no downstream layer
    assert any(f.pass_id == "KNOB-FLAG" and "serve_orphan" in f.message
               for f in found)


def _prefix_v2_tree(*, route_wired=True, gen_validated=True):
    """The prefix-v2 knob pair (--serve-prefix-gen/-route) as a
    minimal bridge fixture: two choices-validated string knobs with
    cli.main coupling guards, breakable one layer at a time."""
    route_wire = ("serve_prefix_route=args.serve_prefix_route,"
                  if route_wired else "")
    gen_post = ('if self.prefix_gen not in ("off", "on"):\n'
                '                        raise ValueError("bad")'
                if gen_validated else "pass")
    return {
        "pkg/cli.py": _src(f"""
            import argparse
            from pkg.config import Config

            def build_parser():
                p = argparse.ArgumentParser()
                p.add_argument("--serve-prefix-gen",
                               choices=["off", "on"], default="off")
                p.add_argument("--serve-prefix-route",
                               choices=["off", "on"], default="off")
                return p

            def config_from_args(args):
                return Config(
                    serve_prefix_gen=args.serve_prefix_gen,
                    {route_wire})

            def main(argv=None):
                args = build_parser().parse_args(argv)
                config = config_from_args(args)
                if config.serve_prefix_gen not in ("off", "on"):
                    raise SystemExit("bad gen")
                if config.serve_prefix_route not in ("off", "on"):
                    raise SystemExit("bad route")
                return config
            """),
        "pkg/config.py": _src("""
            import dataclasses

            @dataclasses.dataclass
            class Config:
                serve_prefix_gen: str = "off"
                serve_prefix_route: str = "off"
            """),
        "pkg/serve.py": _src(f"""
            import dataclasses

            @dataclasses.dataclass
            class ServeConfig:
                prefix_gen: str = "off"
                prefix_route: str = "off"

                def __post_init__(self):
                    {gen_post}
                    if self.prefix_route not in ("off", "on"):
                        raise ValueError("bad")

                @classmethod
                def from_config(cls, cfg):
                    return cls(prefix_gen=cfg.serve_prefix_gen,
                               prefix_route=cfg.serve_prefix_route)

            def use(serve):
                return (serve.prefix_gen, serve.prefix_route)
            """),
    }


def test_prefix_v2_knob_pair_green():
    tree = _prefix_v2_tree()
    assert knob_bridge._find_cli(core.parse_sources(tree)) is not None
    assert knob_bridge.run(tree) == []


def test_prefix_v2_route_not_wired_red():
    found = knob_bridge.run(_prefix_v2_tree(route_wired=False))
    assert any(f.pass_id == "KNOB-FLAG"
               and "serve-prefix-route" in f.message for f in found)


def test_prefix_v2_gen_post_init_missing_red():
    found = knob_bridge.run(_prefix_v2_tree(gen_validated=False))
    assert any(f.pass_id == "KNOB-GUARD"
               and "__post_init__ never validates" in f.message
               and "prefix_gen" in f.message for f in found)


def _mixed_batch_tree(*, budget_wired=True, mixed_validated=True):
    """The mixed-batch knob pair (--serve-mixed-batch/-prefill-budget)
    as a minimal bridge fixture: one choices-validated string knob plus
    one range-guarded int knob, breakable one layer at a time."""
    budget_wire = ("serve_prefill_budget=args.serve_prefill_budget,"
                   if budget_wired else "")
    mixed_post = ('if self.mixed_batch not in ("off", "on"):\n'
                  '                        raise ValueError("bad")'
                  if mixed_validated else "pass")
    return {
        "pkg/cli.py": _src(f"""
            import argparse
            from pkg.config import Config

            def build_parser():
                p = argparse.ArgumentParser()
                p.add_argument("--serve-mixed-batch",
                               choices=["off", "on"], default="off")
                p.add_argument("--serve-prefill-budget",
                               type=int, default=8)
                return p

            def config_from_args(args):
                return Config(
                    serve_mixed_batch=args.serve_mixed_batch,
                    {budget_wire})

            def main(argv=None):
                args = build_parser().parse_args(argv)
                config = config_from_args(args)
                if config.serve_mixed_batch not in ("off", "on"):
                    raise SystemExit("bad mixed")
                if config.serve_prefill_budget < 1:
                    raise SystemExit("bad budget")
                return config
            """),
        "pkg/config.py": _src("""
            import dataclasses

            @dataclasses.dataclass
            class Config:
                serve_mixed_batch: str = "off"
                serve_prefill_budget: int = 8
            """),
        "pkg/serve.py": _src(f"""
            import dataclasses

            @dataclasses.dataclass
            class ServeConfig:
                mixed_batch: str = "off"
                prefill_budget: int = 8

                def __post_init__(self):
                    {mixed_post}
                    if self.prefill_budget < 1:
                        raise ValueError("bad")

                @classmethod
                def from_config(cls, cfg):
                    return cls(mixed_batch=cfg.serve_mixed_batch,
                               prefill_budget=cfg.serve_prefill_budget)

            def use(serve):
                return (serve.mixed_batch, serve.prefill_budget)
            """),
    }


def test_mixed_batch_knob_pair_green():
    tree = _mixed_batch_tree()
    assert knob_bridge._find_cli(core.parse_sources(tree)) is not None
    assert knob_bridge.run(tree) == []


def test_mixed_batch_budget_not_wired_red():
    found = knob_bridge.run(_mixed_batch_tree(budget_wired=False))
    assert any(f.pass_id == "KNOB-FLAG"
               and "serve-prefill-budget" in f.message for f in found)


def test_mixed_batch_post_init_missing_red():
    found = knob_bridge.run(_mixed_batch_tree(mixed_validated=False))
    assert any(f.pass_id == "KNOB-GUARD"
               and "__post_init__ never validates" in f.message
               and "mixed_batch" in f.message for f in found)


def _trace_knob_tree(*, out_wired=True, out_validated=True):
    """The tracing knob pair (--serve-trace/--serve-trace-out) as a
    minimal bridge fixture: one choices-validated mode knob plus one
    path knob whose only semantic guard is the coupling check
    (trace_out requires trace on), breakable one layer at a time."""
    out_wire = ("serve_trace_out=args.serve_trace_out,"
                if out_wired else "")
    out_post = ('if self.trace_out is not None and self.trace != "on":\n'
                '                        raise ValueError("bad")'
                if out_validated else "pass")
    return {
        "pkg/cli.py": _src(f"""
            import argparse
            from pkg.config import Config

            def build_parser():
                p = argparse.ArgumentParser()
                p.add_argument("--serve-trace",
                               choices=["off", "on"], default="off")
                p.add_argument("--serve-trace-out",
                               type=str, default=None)
                return p

            def config_from_args(args):
                return Config(
                    serve_trace=args.serve_trace,
                    {out_wire})

            def main(argv=None):
                args = build_parser().parse_args(argv)
                config = config_from_args(args)
                if config.serve_trace not in ("off", "on"):
                    raise SystemExit("bad trace")
                if config.serve_trace_out is not None:
                    if config.serve_trace != "on":
                        raise SystemExit("out needs trace on")
                return config
            """),
        "pkg/config.py": _src("""
            import dataclasses
            from typing import Optional

            @dataclasses.dataclass
            class Config:
                serve_trace: str = "off"
                serve_trace_out: Optional[str] = None
            """),
        "pkg/serve.py": _src(f"""
            import dataclasses
            from typing import Optional

            @dataclasses.dataclass
            class ServeConfig:
                trace: str = "off"
                trace_out: Optional[str] = None

                def __post_init__(self):
                    if self.trace not in ("off", "on"):
                        raise ValueError("bad")
                    {out_post}

                @classmethod
                def from_config(cls, cfg):
                    return cls(trace=cfg.serve_trace,
                               trace_out=cfg.serve_trace_out)

            def use(serve):
                return (serve.trace, serve.trace_out)
            """),
    }


def test_trace_knob_pair_green():
    tree = _trace_knob_tree()
    assert knob_bridge._find_cli(core.parse_sources(tree)) is not None
    assert knob_bridge.run(tree) == []


def test_trace_out_not_wired_red():
    found = knob_bridge.run(_trace_knob_tree(out_wired=False))
    assert any(f.pass_id == "KNOB-FLAG"
               and "serve-trace-out" in f.message for f in found)


def test_trace_out_post_init_missing_red():
    found = knob_bridge.run(_trace_knob_tree(out_validated=False))
    assert any(f.pass_id == "KNOB-GUARD"
               and "__post_init__ never validates" in f.message
               and "trace_out" in f.message for f in found)


def _kv_ladder_tree(*, group_wired=True, tier_validated=True):
    """The KV capacity-ladder knob pair (--serve-kv-tier/-group) as a
    minimal bridge fixture: one choices-validated mode knob whose only
    semantic guard is the coupling check (tiering rides the prefix
    cache's eviction/match hooks) plus one range-guarded int knob,
    breakable one layer at a time."""
    group_wire = ("serve_kv_group=args.serve_kv_group,"
                  if group_wired else "")
    tier_post = ('if self.kv_tier == "host" and self.prefix == "off":\n'
                 '                        raise ValueError("bad")'
                 if tier_validated else "pass")
    return {
        "pkg/cli.py": _src(f"""
            import argparse
            from pkg.config import Config

            def build_parser():
                p = argparse.ArgumentParser()
                p.add_argument("--serve-kv-tier",
                               choices=["off", "host"], default="off")
                p.add_argument("--serve-kv-group",
                               type=int, default=32)
                return p

            def config_from_args(args):
                return Config(
                    serve_kv_tier=args.serve_kv_tier,
                    {group_wire})

            def main(argv=None):
                args = build_parser().parse_args(argv)
                config = config_from_args(args)
                if config.serve_kv_tier not in ("off", "host"):
                    raise SystemExit("bad tier")
                if config.serve_kv_group < 1:
                    raise SystemExit("bad group")
                return config
            """),
        "pkg/config.py": _src("""
            import dataclasses

            @dataclasses.dataclass
            class Config:
                serve_kv_tier: str = "off"
                serve_kv_group: int = 32
            """),
        "pkg/serve.py": _src(f"""
            import dataclasses

            @dataclasses.dataclass
            class ServeConfig:
                kv_tier: str = "off"
                kv_group: int = 32
                prefix: str = "off"

                def __post_init__(self):
                    {tier_post}
                    if self.kv_group < 1:
                        raise ValueError("bad")

                @classmethod
                def from_config(cls, cfg):
                    return cls(kv_tier=cfg.serve_kv_tier,
                               kv_group=cfg.serve_kv_group)

            def use(serve):
                return (serve.kv_tier, serve.kv_group)
            """),
    }


def test_kv_ladder_knob_pair_green():
    tree = _kv_ladder_tree()
    assert knob_bridge._find_cli(core.parse_sources(tree)) is not None
    assert knob_bridge.run(tree) == []


def test_kv_group_not_wired_red():
    found = knob_bridge.run(_kv_ladder_tree(group_wired=False))
    assert any(f.pass_id == "KNOB-FLAG"
               and "serve-kv-group" in f.message for f in found)


def test_kv_tier_post_init_missing_red():
    found = knob_bridge.run(_kv_ladder_tree(tier_validated=False))
    assert any(f.pass_id == "KNOB-GUARD"
               and "__post_init__ never validates" in f.message
               and "kv_tier" in f.message for f in found)


# ---------------------------------------------------------------------
# recompile-hazard (jit_stability)
# ---------------------------------------------------------------------

def test_jit_branch_red():
    tree = {"pkg/m.py": _src("""
        import jax

        @jax.jit
        def f(x, flag):
            if flag:
                return x + 1
            return x
        """)}
    found = jit_stability.run(tree)
    assert _ids(found) == ["JIT-BRANCH"]
    assert "'flag'" in found[0].message


def test_jit_branch_static_forms_green():
    tree = {"pkg/m.py": _src("""
        import jax

        @jax.jit
        def f(x, y):
            if x is None:
                return y
            if isinstance(y, tuple):
                y = y[0]
            if x.shape[0] > 4:
                return x * 2
            if len(x.shape) == 2:
                return x
            return x + y
        """)}
    assert jit_stability.run(tree) == []


def test_jit_branch_reaches_through_jit_callsite():
    tree = {"pkg/m.py": _src("""
        import jax

        def impl(x, n):
            while n > 0:
                x = x + 1
            return x

        step = jax.jit(impl)
        """)}
    assert _ids(jit_stability.run(tree)) == ["JIT-BRANCH"]


def test_jit_branch_nested_param_does_not_shadow_outer_static():
    # the bert `causal` regression: a DESCENDANT def's param name must
    # not mark the same name traced at an OUTER branch, where it binds
    # to a closure-captured static config value
    tree = {"pkg/m.py": _src("""
        import jax

        def make(causal):
            def outer(q):
                if causal:
                    def inner(q, causal=False):
                        return q
                    return inner(q)
                return q
            return jax.jit(outer)
        """)}
    assert jit_stability.run(tree) == []


def test_jit_loop_red_and_allowlist():
    body = """
        import jax

        def probe(chunks, f):
            for s in chunks:
                {marker}jax.jit(f).lower(s).compile()
            return True
        """
    red = {"pkg/m.py": _src(body.format(marker=""))}
    assert _ids(jit_stability.run(red)) == ["JIT-LOOP"]
    green = {"pkg/m.py": _src(body.format(
        marker="# graft-lint: jit-ok(compile probe)\n"
               "                "))}
    assert jit_stability.run(green) == []


def test_jit_shape_red_in_serving_only():
    body = _src("""
        import numpy as np

        def dispatch(live):
            n = len(live)
            buf = np.zeros((n, 4), np.int32)
            return buf
        """)
    assert _ids(jit_stability.run({"pkg/serving/d.py": body})) \
        == ["JIT-SHAPE"]
    # outside serving/ the discipline doesn't apply
    assert jit_stability.run({"pkg/train/d.py": body}) == []


def test_jit_shape_bucketed_green():
    tree = {"pkg/serving/d.py": _src("""
        import numpy as np

        def pow2_ceil(n):
            return max(1, 1 << (n - 1).bit_length())

        def dispatch(live):
            n = pow2_ceil(len(live))
            return np.zeros((n, 4), np.int32)
        """)}
    assert jit_stability.run(tree) == []


# ---------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------

def _hot_module(step_body):
    return {"pkg/serving/iteration.py": _src(f"""
        import jax
        import numpy as np

        class Loop:
            def __init__(self, impl):
                self._decode_fn = jax.jit(impl)

            def step(self, tokens):
                {step_body}
        """)}


def test_host_sync_cast_red():
    tree = _hot_module("""nxt = self._decode_fn(tokens)
                return int(nxt)""")
    found = host_sync.run(tree)
    assert _ids(found) == ["HOST-SYNC"]
    assert "int()" in found[0].message


def test_host_sync_item_red():
    tree = _hot_module("""nxt = self._decode_fn(tokens)
                return nxt.item()""")
    assert any(".item()" in f.message for f in host_sync.run(tree))


def test_host_sync_allowlist_green():
    tree = _hot_module("""nxt = self._decode_fn(tokens)
                # graft-lint: sync-ok(the one budgeted bulk sync)
                return np.asarray(nxt)""")
    assert host_sync.run(tree) == []


def test_host_sync_untainted_green():
    # int() on plain host data is not a sync
    tree = _hot_module("""n = len(tokens)
                return int(n)""")
    assert host_sync.run(tree) == []


def test_host_sync_rebinding_clears_taint():
    tree = _hot_module("""nxt = self._decode_fn(tokens)
                nxt = [1, 2, 3]
                return int(nxt[0])""")
    assert host_sync.run(tree) == []


def test_host_sync_trace_stamp_red():
    # a span-stamping callback must not smuggle a device sync: reading
    # the dispatched output to decorate a trace event blocks the serve
    # loop on the device — tracing's contract is host clocks ONLY
    tree = _hot_module("""nxt = self._decode_fn(tokens)
                self.tracer.event(float(nxt), "first_token")
                return nxt""")
    found = host_sync.run(tree)
    assert _ids(found) == ["HOST-SYNC"]
    assert "float()" in found[0].message


def test_host_sync_cold_namespace_green():
    # same code outside the hot namespace: not this pass's business
    tree = {"pkg/serving/other.py": _src("""
        import jax

        class Loop:
            def __init__(self, impl):
                self._decode_fn = jax.jit(impl)

            def step(self, tokens):
                return int(self._decode_fn(tokens))
        """)}
    assert host_sync.run(tree) == []


# ---------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------

_PR7_RACE = """
    import threading
    from collections import OrderedDict

    class Router:
        _GUARDED_BY = {"_lock": ("_sticky",)}

        def __init__(self):
            self._lock = threading.Lock()
            self._sticky = OrderedDict()

        def route(self, session):
            with self._lock:
                replica = self._sticky.get(session)
            if replica is not None and self.healthy(replica):
                with self._lock:
                    self._sticky.move_to_end(session)
            return replica

        def healthy(self, replica):
            return True
    """

_PR7_FIXED = _PR7_RACE.replace(
    """with self._lock:
                replica = self._sticky.get(session)
            if replica is not None and self.healthy(replica):
                with self._lock:
                    self._sticky.move_to_end(session)""",
    """with self._lock:
                replica = self._sticky.get(session)
                if replica is not None and self.healthy(replica):
                    self._sticky.move_to_end(session)""")


def test_lock_pr7_sticky_race_fixture():
    # the shipped PR 7 bug shape: get() under one hold, the LRU touch
    # under ANOTHER — a concurrent trim can evict the key between them.
    # Lexically both accesses ARE under some `with self._lock`, so the
    # per-access proof passes; what the fixed shape pins is ONE hold
    # spanning read + health check + touch.
    red = {"pkg/r.py": _src(_PR7_RACE)}
    assert locks.run(red) == []          # each access is under A lock…
    green = {"pkg/r.py": _src(_PR7_FIXED)}
    assert locks.run(green) == []        # …and so is the fixed shape;
    # the race the pass DOES catch statically: the touch with no hold
    naked = {"pkg/r.py": _src(_PR7_RACE.replace(
        """with self._lock:
                    self._sticky.move_to_end(session)""",
        "self._sticky.move_to_end(session)"))}
    found = locks.run(naked)
    assert _ids(found) == ["LOCK-HELD"]
    assert "PR 7" in found[0].message


def test_lock_init_and_locked_suffix_exempt():
    tree = {"pkg/r.py": _src("""
        import threading

        class Router:
            _GUARDED_BY = {"_lock": ("_state",)}

            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}

            def _trim_locked(self):
                self._state.clear()

            def trim(self):
                with self._lock:
                    self._trim_locked()
        """)}
    assert locks.run(tree) == []


def test_lock_allowlist_comment():
    tree = {"pkg/r.py": _src("""
        import threading

        class Router:
            _GUARDED_BY = {"_lock": ("_state",)}

            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}

            def reset(self):
                # graft-lint: lock-ok(cold path: no workers yet)
                self._state = {}
        """)}
    assert locks.run(tree) == []
    # without the comment the same store is a finding
    stripped = {"pkg/r.py": tree["pkg/r.py"].replace(
        "        # graft-lint: lock-ok(cold path: no workers yet)\n",
        "")}
    assert _ids(locks.run(stripped)) == ["LOCK-HELD"]


def test_lock_undeclared_class_not_checked():
    tree = {"pkg/r.py": _src("""
        class Plain:
            def poke(self):
                self._state = 1
        """)}
    assert locks.run(tree) == []


# ---------------------------------------------------------------------
# names
# ---------------------------------------------------------------------

def test_names_undefined_red():
    # the reference-repo bug shape: an exception handler raising a
    # never-imported name
    tree = {"pkg/m.py": _src("""
        def fetch(url):
            try:
                return open(url)
            except OSError:
                raise DownloadError(url)
        """)}
    found = names.run(tree)
    assert _ids(found) == ["NAMES-UNDEF"]
    assert "DownloadError" in found[0].message


def test_names_unused_import_red_and_noqa():
    tree = {"pkg/m.py": "import os\nimport sys\n\nprint(sys.argv)\n"}
    found = names.run(tree)
    assert _ids(found) == ["NAMES-IMPORT"]
    assert "'os'" in found[0].message
    quiet = {"pkg/m.py": tree["pkg/m.py"].replace(
        "import os", "import os  # noqa: re-export")}
    assert names.run(quiet) == []


def test_names_init_reexports_and_star_imports_skipped():
    tree = {
        "pkg/__init__.py": "from pkg.m import helper\n",
        "pkg/star.py": "from os.path import *\n\nprint(join('a'))\n",
    }
    assert names.run(tree) == []


def test_names_clean_module_green():
    tree = {"pkg/m.py": _src("""
        import os

        def here():
            return os.getcwd()
        """)}
    assert names.run(tree) == []


# ---------------------------------------------------------------------
# runner + baseline ratchet
# ---------------------------------------------------------------------

def _fake_repo(tmp_path, n_bugs):
    pkg = tmp_path / "mpi_tensorflow_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    body = "import jax\n\n\n@jax.jit\ndef f(x, flag):\n"
    for _ in range(n_bugs):
        body += "    if flag:\n        x = x + 1\n"
    body += "    return x\n"
    (pkg / "m.py").write_text(body)
    return tmp_path


def test_runner_exit_codes_and_ratchet(tmp_path, capsys):
    root = _fake_repo(tmp_path, n_bugs=1)
    baseline = tmp_path / "baseline.json"

    # no baseline: the finding is new -> exit 1, printed
    rc = runner.main(["--root", str(root), "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 1 and "JIT-BRANCH" in out

    # baseline it -> clean run exits 0 and stays silent about it
    assert runner.main(["--root", str(root), "--baseline",
                        str(baseline), "--update-baseline"]) == 0
    capsys.readouterr()
    rc = runner.main(["--root", str(root), "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 0 and "JIT-BRANCH" not in out

    # a SECOND violation exceeds the baselined count -> exit 1, and
    # only the excess is reported as new
    _fake_repo(tmp_path, n_bugs=2)
    rc = runner.main(["--root", str(root), "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 1 and out.count("JIT-BRANCH") == 1

    # the ratchet: --update-baseline REFUSES to grow a count
    rc = runner.main(["--root", str(root), "--baseline",
                      str(baseline), "--update-baseline"])
    err = capsys.readouterr().err
    assert rc == 1 and "ratchet" in err
    assert json.loads(baseline.read_text()) \
        == {"JIT-BRANCH:mpi_tensorflow_tpu/m.py": 1}

    # fixing BOTH and re-baselining ratchets down to empty
    _fake_repo(tmp_path, n_bugs=0)
    assert runner.main(["--root", str(root), "--baseline",
                        str(baseline), "--update-baseline"]) == 0
    assert json.loads(baseline.read_text()) == {}


def test_runner_all_passes_registered():
    mods = {m.__name__.rsplit(".", 1)[-1] for m in runner.PASSES}
    assert mods == {"knob_bridge", "jit_stability", "host_sync",
                    "locks", "names"}
    ids = [pid for m in runner.PASSES for pid in m.PASS_IDS]
    assert len(ids) == len(set(ids)) == 10


def test_live_repo_scans_clean():
    """The repo's own contracts hold: every finding either fixed or
    allowlisted in-source, baseline (near-)empty — the PR's acceptance
    bar, pinned."""
    sources = core.load_sources(core.repo_root())
    assert "mpi_tensorflow_tpu/serving/router.py" in sources
    assert "bench.py" in sources
    findings = runner.run_all(sources)
    baseline = runner.load_baseline(runner._DEFAULT_BASELINE)
    assert sum(baseline.values()) <= 5, \
        "the baseline is a ratchet, not a dumping ground"
    over = runner.compare(runner.counts_by_key(findings), baseline)
    assert over == {}, [f.format() for f in findings]


def test_finding_format_matches_contract():
    f = core.Finding("pkg/m.py", 7, "HOST-SYNC", "boom")
    assert f.format() == "pkg/m.py:7: HOST-SYNC boom"
    assert f.baseline_key == "HOST-SYNC:pkg/m.py"
